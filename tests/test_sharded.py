"""Multi-device golden tests on the 8-device CPU mesh (the minicluster analog)."""

import random

import numpy as np
import pytest

import jax

from rdfind_tpu.dictionary import intern_triples
from rdfind_tpu.models import allatonce, sharded
from rdfind_tpu.parallel.mesh import make_mesh
from rdfind_tpu.utils.synth import generate_triples


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest should provide 8 CPU devices"
    return make_mesh(8)


def random_triples(rng, n, n_subj, n_pred, n_obj):
    return [
        (f"s{rng.randrange(n_subj)}", f"p{rng.randrange(n_pred)}",
         f"o{rng.randrange(n_obj)}")
        for _ in range(n)
    ]


@pytest.mark.parametrize("seed", range(3))
@pytest.mark.parametrize("min_support", [1, 3])
def test_sharded_matches_single_chip(mesh8, seed, min_support):
    rng = random.Random(seed)
    ids, _ = intern_triples(np.asarray(random_triples(rng, 90, 6, 3, 5), dtype=object))
    a = sharded.discover_sharded(ids, min_support, mesh=mesh8)
    b = allatonce.discover(ids, min_support)
    assert a.to_rows() == b.to_rows()


def test_sharded_synthetic_workload(mesh8):
    triples = generate_triples(300, seed=5, n_predicates=8, n_entities=32)
    a = sharded.discover_sharded(triples, 2, mesh=mesh8)
    b = allatonce.discover(triples, 2)
    assert a.to_rows() == b.to_rows()


@pytest.mark.slow
def test_sharded_device_counts(min_support=2):
    # The result must not depend on the mesh size.
    triples = generate_triples(150, seed=6, n_predicates=6, n_entities=24)
    want = allatonce.discover(triples, min_support).to_rows()
    for d in (1, 2, 4, 8):
        mesh = make_mesh(d)
        got = sharded.discover_sharded(triples, min_support, mesh=mesh).to_rows()
        assert got == want, f"mismatch on {d}-device mesh"


def test_sharded_projections(mesh8):
    triples = generate_triples(150, seed=8, n_predicates=6, n_entities=24)
    for proj in ("s", "so"):
        a = sharded.discover_sharded(triples, 2, mesh=mesh8, projections=proj)
        b = allatonce.discover(triples, 2, projections=proj)
        assert a.to_rows() == b.to_rows()


def test_sharded_empty(mesh8):
    out = sharded.discover_sharded(np.zeros((0, 3), np.int32), 2, mesh=mesh8)
    assert len(out) == 0


def skewed_triples(rng, n_hot, n_cold):
    """One scorching join value (o0 shared by n_hot distinct (s,p) combos) plus a
    cold tail — the power-law shape the skew engine exists for."""
    rows = [(f"s{i}", f"p{i % 5}", "o0") for i in range(n_hot)]
    rows += [(f"s{rng.randrange(40)}", f"p{rng.randrange(5)}",
              f"o{1 + rng.randrange(30)}") for _ in range(n_cold)]
    rng.shuffle(rows)
    return rows


@pytest.mark.parametrize("min_support", [1, 3])
def test_skew_split_matches_single_chip(mesh8, min_support):
    rng = random.Random(11)
    ids, _ = intern_triples(
        np.asarray(skewed_triples(rng, 120, 200), dtype=object))
    stats = {}
    a = sharded.discover_sharded(ids, min_support, mesh=mesh8, stats=stats)
    b = allatonce.discover(ids, min_support)
    assert a.to_rows() == b.to_rows()
    # The hot line must actually have been routed through the split path.
    assert stats["n_giant_lines"] >= 1
    assert stats["n_giant_pairs"] > 0


def test_tiny_input_small_mesh():
    # Regression: cap_giant larger than the whole row buffer must not break the
    # gather slicing (4 triples on 1-/2-device meshes).
    ids, _ = intern_triples(np.asarray(
        [("s1", "p1", "o1"), ("s2", "p1", "o1"), ("s1", "p2", "o2"),
         ("s2", "p2", "o2")], dtype=object))
    want = allatonce.discover(ids, 1).to_rows()
    for d in (1, 2):
        got = sharded.discover_sharded(ids, 1, mesh=make_mesh(d)).to_rows()
        assert got == want


@pytest.mark.slow
def test_skew_split_device_invariance(mesh8):
    rng = random.Random(12)
    ids, _ = intern_triples(
        np.asarray(skewed_triples(rng, 80, 120), dtype=object))
    want = allatonce.discover(ids, 2).to_rows()
    for d in (1, 4, 8):
        got = sharded.discover_sharded(ids, 2, mesh=make_mesh(d)).to_rows()
        assert got == want, f"mismatch on {d}-device mesh"


# ---------------------------------------------------------------------------
# Distributed frequency filter + sharded SmallToLarge (round 2).
# ---------------------------------------------------------------------------

from rdfind_tpu.models import small_to_large  # noqa: E402


@pytest.mark.parametrize("use_fis,use_ars",
                         [(False, False), (True, False), (True, True)])
def test_sharded_fis_ars_matches_single_chip(mesh8, use_fis, use_ars):
    """The distributed frequency filter + AR suppression must be output-
    identical to the single-device AllAtOnce with the same flags."""
    triples = generate_triples(300, seed=9, n_predicates=6, n_entities=24)
    a = sharded.discover_sharded(triples, 2, mesh=mesh8, use_fis=use_fis,
                                 use_ars=use_ars)
    b = allatonce.discover(triples, 2, use_frequent_condition_filter=use_fis,
                           use_association_rules=use_ars)
    assert a.to_rows() == b.to_rows()


@pytest.mark.parametrize("min_support", [1, 3])
@pytest.mark.parametrize("seed", range(2))
def test_sharded_s2l_matches_single_chip(mesh8, seed, min_support):
    """Sharded S2L (default strategy distributed) == single-device S2L."""
    rng = random.Random(seed)
    ids, _ = intern_triples(
        np.asarray(random_triples(rng, 90, 6, 3, 5), dtype=object))
    a = sharded.discover_sharded_s2l(ids, min_support, mesh=mesh8)
    b = small_to_large.discover(ids, min_support)
    assert a.to_rows() == b.to_rows()


@pytest.mark.parametrize("use_fis,use_ars",
                         [(False, False), (True, False), (True, True)])
def test_sharded_s2l_flags(mesh8, use_fis, use_ars):
    triples = generate_triples(250, seed=11, n_predicates=6, n_entities=20)
    a = sharded.discover_sharded_s2l(triples, 2, mesh=mesh8, use_fis=use_fis,
                                     use_ars=use_ars)
    b = small_to_large.discover(triples, 2,
                                use_frequent_condition_filter=use_fis,
                                use_association_rules=use_ars)
    assert a.to_rows() == b.to_rows()


def test_sharded_s2l_skew_split(mesh8):
    """A hot join value must drive the S2L giant-line path and stay correct."""
    triples = generate_triples(150, seed=13, n_predicates=5, n_entities=16)
    hot = np.stack([np.arange(100, 160, dtype=np.int32),
                    np.arange(60, dtype=np.int32) % 3 + 500,
                    np.full(60, 999, dtype=np.int32)], axis=1)
    triples = np.concatenate([np.asarray(triples, np.int32), hot])
    stats = {}
    a = sharded.discover_sharded_s2l(triples, 2, mesh=mesh8, stats=stats)
    b = small_to_large.discover(triples, 2)
    assert a.to_rows() == b.to_rows()
    assert stats["n_giant_lines"] >= 1  # the split path actually fired


@pytest.mark.slow
def test_sharded_s2l_device_invariance():
    triples = generate_triples(120, seed=17, n_predicates=4, n_entities=12)
    want = small_to_large.discover(triples, 2).to_rows()
    for d in (1, 2, 4, 8):
        got = sharded.discover_sharded_s2l(triples, 2, mesh=make_mesh(d)).to_rows()
        assert got == want, f"mismatch at {d} devices"


def test_global_row_counts_roundtrip(mesh8):
    """exchange.global_row_counts must equal a host group-count, per row."""
    import functools

    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from rdfind_tpu.parallel import exchange
    from rdfind_tpu.parallel.mesh import AXIS, shard_map

    rng = np.random.default_rng(0)
    n = 256  # 32 rows/device
    keys = rng.integers(0, 37, n).astype(np.int32)
    valid = rng.random(n) < 0.9

    def step(k, v):
        c, ovf = exchange.global_row_counts([k], v, AXIS, 64, seed=3)
        return c, jnp.full(1, ovf, jnp.int32)

    fn = jax.jit(shard_map(
        step, mesh=mesh8, in_specs=(P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P(AXIS)), check_vma=False))
    counts, ovf = fn(jnp.asarray(keys), jnp.asarray(valid))
    assert int(np.asarray(ovf).sum()) == 0
    want = np.zeros(n, np.int64)
    uniq, inv = np.unique(keys[valid], return_inverse=True)
    cnt = np.bincount(inv)
    lut = dict(zip(uniq.tolist(), cnt.tolist()))
    for i in range(n):
        want[i] = lut.get(int(keys[i]), 0) if valid[i] else 0
    assert np.array_equal(np.asarray(counts), want)


def test_capacity_plan_scales_with_load(mesh8):
    """Planned per-device buffers must track measured loads (~N/D + skew), not
    the old 'everything lands on one device' worst cases (VERDICT r1 weak #3).
    """
    # Sized to share the floored per-device block (t_loc = T_LOC_FLOOR) with
    # the rest of the suite, so the pipeline compiles are reused.
    triples = generate_triples(800, seed=21, n_predicates=8, n_entities=64)
    # One hot join value so the plan includes real skew.
    hot = np.stack([np.arange(100, 160, dtype=np.int32),
                    np.arange(60, dtype=np.int32) % 4 + 900,
                    np.full(60, 7777, dtype=np.int32)], axis=1)
    triples = np.concatenate([np.asarray(triples, np.int32), hot])
    stats = {}
    a = sharded.discover_sharded(triples, 2, mesh=mesh8, stats=stats)
    b = allatonce.discover(triples, 2)
    assert a.to_rows() == b.to_rows()

    caps = stats["planned_caps"]
    num_dev = 8
    n = triples.shape[0]
    t_loc = max(sharded.T_LOC_FLOOR,
                1 << (-(-n // num_dev) - 1).bit_length())
    # The old worst-case formulas (sharded.py r1: cap_b = pow2(D*cap_a),
    # cap_p = pow2(4*D*cap_a)) for this workload:
    def pow2(x):
        return 1 << max(0, int(x) - 1).bit_length() if x > 1 else 1
    old_cap_a = pow2(9 * t_loc)
    old_cap_b = pow2(num_dev * old_cap_a)
    old_cap_p = pow2(4 * num_dev * old_cap_a)
    # Planned row exchanges must be far below the worst cases...
    assert caps["exchange_a"] <= old_cap_a // 2
    assert caps["exchange_b"] <= old_cap_b // 8
    assert caps["pairs"] <= old_cap_p // 2
    # ...and within a constant factor of the per-device share of the real load
    # (pow2 bucketing + 12.5% margin => <= 4x the measured maximum, which is
    # itself >= share/D of the global row count).
    assert caps["exchange_b"] <= 4 * (9 * n // num_dev + 80)


# ---------------------------------------------------------------------------
# Sharded approximate strategies (2: ApproximateAllAtOnce, 3: LateBB).
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", range(2))
@pytest.mark.parametrize("min_support", [1, 3])
def test_sharded_approx_matches_single_chip(mesh8, seed, min_support):
    from rdfind_tpu.models import approximate
    rng = random.Random(seed + 500)
    ids, _ = intern_triples(
        np.asarray(random_triples(rng, 120, 6, 3, 5), dtype=object))
    stats = {}
    a = sharded.discover_sharded_approx(ids, min_support, mesh=mesh8,
                                        stats=stats)
    b = approximate.discover(ids, min_support)
    assert a.to_rows() == b.to_rows()
    assert stats["n_sketch_candidates"] > 0


def test_sharded_late_bb_matches_single_chip(mesh8):
    from rdfind_tpu.models import late_bb
    rng = random.Random(77)
    ids, _ = intern_triples(
        np.asarray(random_triples(rng, 120, 6, 3, 5), dtype=object))
    a = sharded.discover_sharded_late_bb(ids, 2, mesh=mesh8)
    b = late_bb.discover(ids, 2)
    assert a.to_rows() == b.to_rows()


def test_sharded_approx_flags(mesh8):
    from rdfind_tpu.models import approximate
    rng = random.Random(78)
    ids, _ = intern_triples(
        np.asarray(random_triples(rng, 100, 5, 3, 4), dtype=object))
    a = sharded.discover_sharded_approx(ids, 2, mesh=mesh8, use_fis=True,
                                        use_ars=True, clean_implied=True)
    b = approximate.discover(ids, 2, use_frequent_condition_filter=True,
                             use_association_rules=True, clean_implied=True)
    assert a.to_rows() == b.to_rows()


def midskew_triples(n_groups=32, group_len=7):
    """Many mid-sized hot join lines (above-average load, below every giant
    threshold) + a cold tail of 3-capture lines: the shape where pure hash
    placement can pile hot lines onto one device while the split engine —
    which only fires on giant lines — never helps."""
    rows = []
    s = 0
    for g in range(n_groups):
        for _ in range(group_len):
            rows.append((s, 5000 + g, 10000 + g))
            s += 1
    return np.asarray(rows, np.int32)


def test_load_aware_placement(mesh8):
    triples = midskew_triples()
    stats = {}
    a = sharded.discover_sharded(triples, 2, mesh=mesh8, stats=stats)
    b = allatonce.discover(triples, 2)
    assert a.to_rows() == b.to_rows()
    # These lines are hot but NOT giant: the greedy placement, not the split
    # engine, is what handles them.
    assert stats["n_giant_lines"] == 0
    reb = stats["rebalance"]
    assert reb["hot_lines"] >= 2 * 32  # one o-line and one p-line per group
    assert reb["moved_lines"] > 0
    assert reb["load_max_over_mean_planned"] <= 2.0
    assert (reb["load_max_over_mean_planned"]
            < reb["load_max_over_mean_before"])


def test_load_aware_placement_s2l(mesh8):
    """The default strategy shares the pipeline, so placement must not change
    its output either."""
    from rdfind_tpu.models import small_to_large
    triples = midskew_triples(n_groups=16)
    stats = {}
    a = sharded.discover_sharded_s2l(triples, 2, mesh=mesh8, stats=stats)
    b = small_to_large.discover(triples, 2)
    assert a.to_rows() == b.to_rows()


def test_route_scattered_valid(mesh8):
    """route() must deliver rows whose valid mask is NOT a compacted prefix
    (regression: the validity lane was permuted twice, which only worked by
    accident for prefix masks)."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from rdfind_tpu.parallel import exchange
    from rdfind_tpu.parallel.mesh import AXIS, shard_map

    n, cap = 64, 16

    def step(col, valid):
        bucket = col % 8
        out, out_valid, ovf = exchange.bucket_exchange(
            [col], valid, bucket, AXIS, cap)
        got = jnp.where(out_valid, out[0], 0).sum()
        return jnp.full(1, got, jnp.int32), jnp.full(1, ovf, jnp.int32)

    rng = np.random.default_rng(3)
    col = rng.integers(0, 1000, size=8 * n).astype(np.int32)
    valid = rng.random(8 * n) < 0.3  # scattered, sparse
    got, ovf = shard_map(
        step, mesh=mesh8, in_specs=(P(AXIS), P(AXIS)), out_specs=P(AXIS),
        check_vma=False)(jnp.asarray(col), jnp.asarray(valid))
    assert int(np.asarray(ovf)[0]) == 0
    assert int(np.asarray(got).sum()) == int(col[valid].sum())


def test_skew_policy_range_slice(mesh8):
    """Split strategy 2 (contiguous range-slice ownership) must produce the
    same output as the default hash-slice on a workload where the split
    engine provably fires."""
    rng = random.Random(11)
    ids, _ = intern_triples(
        np.asarray(skewed_triples(rng, 120, 200), dtype=object))
    want = allatonce.discover(ids, 2).to_rows()
    stats = {}
    a = sharded.discover_sharded(ids, 2, mesh=mesh8, stats=stats,
                                 skew=sharded.SkewPolicy(strategy=2))
    assert a.to_rows() == want
    assert stats["n_giant_lines"] >= 1  # the split path really ran


def test_skew_policy_max_load(mesh8):
    """--rebalance-max-load forces mid-sized lines through the split path."""
    triples = midskew_triples()
    base_stats = {}
    want = sharded.discover_sharded(triples, 2, mesh=mesh8,
                                    stats=base_stats).to_rows()
    assert base_stats["n_giant_lines"] == 0  # not giant under defaults
    stats = {}
    a = sharded.discover_sharded(
        triples, 2, mesh=mesh8, stats=stats,
        skew=sharded.SkewPolicy(max_load=100.0))
    assert a.to_rows() == want
    assert stats["n_giant_lines"] > 0  # max_load made them split


def test_no_combinable_join(mesh8):
    """The --no-combinable-join ablation (raw candidate rows into exchange A)
    must not change the output."""
    triples = generate_triples(150, seed=8, n_predicates=6, n_entities=24)
    want = sharded.discover_sharded(triples, 2, mesh=mesh8).to_rows()
    got = sharded.discover_sharded(triples, 2, mesh=mesh8,
                                   combine=False).to_rows()
    assert got == want


def test_skew_policy_validation():
    with pytest.raises(ValueError, match="rebalance strategy"):
        sharded.SkewPolicy(strategy=3)


def test_host_capture_budget_guard(mesh8, monkeypatch):
    """The host-side lattice pull fails loudly past its stated budget."""
    import os
    monkeypatch.setitem(os.environ, "RDFIND_HOST_CAPTURES_BUDGET", "4")
    triples = generate_triples(100, seed=2, n_predicates=4, n_entities=16)
    with pytest.raises(ValueError, match="lattice budget"):
        sharded.discover_sharded_s2l(triples, 2, mesh=mesh8)


def _make_preshard(ids, mesh):
    """Single-process preshard via the production layout helper (the same
    contiguous split + per-device valid prefixes sharded_ingest assembles)."""
    from rdfind_tpu.parallel.mesh import make_global

    padded, n_valid, _ = sharded._shard_triples(np.asarray(ids, np.int32),
                                                mesh.devices.size)
    return make_global(padded, mesh), make_global(n_valid, mesh)


def test_preshard_use_ars_matches_host_mining(mesh8):
    """Distributed AR mining over a preshard == host mining: same rule table,
    same AR-filtered CINDs (the lifted --sharded-ingest --use-ars path)."""
    from rdfind_tpu.ops import frequency

    rng = random.Random(31)
    rows = random_triples(rng, 80, 5, 3, 4)
    rows += [("s_ar", "p_ar", f"o{i}") for i in range(4)] * 3  # a real rule
    ids, _ = intern_triples(np.asarray(rows, dtype=object))
    g_triples, g_valid = _make_preshard(ids, mesh8)

    want_rules = frequency.mine_association_rules(ids, 2)
    got_rules = sharded.mine_ars_sharded(g_triples, g_valid, 2, mesh8)
    to_set = lambda cols: {tuple(int(c[i]) for c in cols)
                           for i in range(len(cols[0]))}
    assert to_set(got_rules) == to_set(want_rules)
    assert len(want_rules[0]) > 0  # the fixture really mines rules

    for fn in (sharded.discover_sharded, sharded.discover_sharded_s2l,
               sharded.discover_sharded_approx,
               sharded.discover_sharded_late_bb):
        want = fn(ids, 2, mesh=mesh8, use_fis=True, use_ars=True).to_rows()
        got = fn(None, 2, mesh=mesh8, use_fis=True, use_ars=True,
                 preshard=(g_triples, g_valid)).to_rows()
        assert got == want, fn.__name__


def test_join_histogram_sharded_matches_host(mesh8):
    from rdfind_tpu.runtime.driver import _join_histogram

    triples = generate_triples(200, seed=12, n_predicates=6, n_entities=24)
    ids = np.asarray(triples, np.int32)
    g_triples, g_valid = _make_preshard(ids, mesh8)
    got = sharded.join_histogram_sharded(g_triples, g_valid, "spo", mesh8)
    want = _join_histogram(ids, "spo")
    assert got == want


def test_sharded_multipass_pair_phase(mesh8, monkeypatch):
    """A tiny pair-row budget must force dep-slice streaming passes (the
    bounded-memory pair phase) on BOTH strategies, with identical output."""
    triples = generate_triples(300, seed=21, n_predicates=8, n_entities=32)
    # 2^13 rows => 2-3 passes (enough to exercise slicing without tens of
    # per-pass dispatches dominating the fast tier).
    monkeypatch.setattr(sharded, "PAIR_ROW_BUDGET", 1 << 13)
    s0, s1 = {}, {}
    a = sharded.discover_sharded(triples, 2, mesh=mesh8, stats=s0)
    b = sharded.discover_sharded_s2l(triples, 2, mesh=mesh8, stats=s1)
    assert s0["n_pair_passes"] > 1
    assert s1["n_pair_passes"] > 1
    want = allatonce.discover(triples, 2)
    assert a.to_rows() == want.to_rows()
    assert b.to_rows() == small_to_large.discover(triples, 2).to_rows()


@pytest.mark.slow
@pytest.mark.parametrize("seed", [31, 37, 41])
def test_sharded_multipass_fuzz(mesh8, monkeypatch, seed):
    """Streaming passes stay exact across random workloads (slow tier):
    every strategy-0 run with a tiny budget must equal the single-chip
    oracle regardless of how the dep slices cut the capture space."""
    rng = random.Random(seed)
    ids, _ = intern_triples(np.asarray(
        random_triples(rng, 250, 10, 4, 8), dtype=object))
    monkeypatch.setattr(sharded, "PAIR_ROW_BUDGET", 1 << 12)
    s: dict = {}
    a = sharded.discover_sharded(ids, 2, mesh=mesh8, stats=s)
    assert s["n_pair_passes"] > 1
    assert a.to_rows() == allatonce.discover(ids, 2).to_rows()
