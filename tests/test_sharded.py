"""Multi-device golden tests on the 8-device CPU mesh (the minicluster analog)."""

import random

import numpy as np
import pytest

import jax

from rdfind_tpu.dictionary import intern_triples
from rdfind_tpu.models import allatonce, sharded
from rdfind_tpu.parallel.mesh import make_mesh
from rdfind_tpu.utils.synth import generate_triples


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest should provide 8 CPU devices"
    return make_mesh(8)


def random_triples(rng, n, n_subj, n_pred, n_obj):
    return [
        (f"s{rng.randrange(n_subj)}", f"p{rng.randrange(n_pred)}",
         f"o{rng.randrange(n_obj)}")
        for _ in range(n)
    ]


@pytest.mark.parametrize("seed", range(3))
@pytest.mark.parametrize("min_support", [1, 3])
def test_sharded_matches_single_chip(mesh8, seed, min_support):
    rng = random.Random(seed)
    ids, _ = intern_triples(np.asarray(random_triples(rng, 90, 6, 3, 5), dtype=object))
    a = sharded.discover_sharded(ids, min_support, mesh=mesh8)
    b = allatonce.discover(ids, min_support)
    assert a.to_rows() == b.to_rows()


def test_sharded_synthetic_workload(mesh8):
    triples = generate_triples(300, seed=5, n_predicates=8, n_entities=32)
    a = sharded.discover_sharded(triples, 2, mesh=mesh8)
    b = allatonce.discover(triples, 2)
    assert a.to_rows() == b.to_rows()


def test_sharded_device_counts(min_support=2):
    # The result must not depend on the mesh size.
    triples = generate_triples(150, seed=6, n_predicates=6, n_entities=24)
    want = allatonce.discover(triples, min_support).to_rows()
    for d in (1, 2, 4, 8):
        mesh = make_mesh(d)
        got = sharded.discover_sharded(triples, min_support, mesh=mesh).to_rows()
        assert got == want, f"mismatch on {d}-device mesh"


def test_sharded_projections(mesh8):
    triples = generate_triples(150, seed=8, n_predicates=6, n_entities=24)
    for proj in ("s", "so"):
        a = sharded.discover_sharded(triples, 2, mesh=mesh8, projections=proj)
        b = allatonce.discover(triples, 2, projections=proj)
        assert a.to_rows() == b.to_rows()


def test_sharded_empty(mesh8):
    out = sharded.discover_sharded(np.zeros((0, 3), np.int32), 2, mesh=mesh8)
    assert len(out) == 0


def skewed_triples(rng, n_hot, n_cold):
    """One scorching join value (o0 shared by n_hot distinct (s,p) combos) plus a
    cold tail — the power-law shape the skew engine exists for."""
    rows = [(f"s{i}", f"p{i % 5}", "o0") for i in range(n_hot)]
    rows += [(f"s{rng.randrange(40)}", f"p{rng.randrange(5)}",
              f"o{1 + rng.randrange(30)}") for _ in range(n_cold)]
    rng.shuffle(rows)
    return rows


@pytest.mark.parametrize("min_support", [1, 3])
def test_skew_split_matches_single_chip(mesh8, min_support):
    rng = random.Random(11)
    ids, _ = intern_triples(
        np.asarray(skewed_triples(rng, 120, 200), dtype=object))
    stats = {}
    a = sharded.discover_sharded(ids, min_support, mesh=mesh8, stats=stats)
    b = allatonce.discover(ids, min_support)
    assert a.to_rows() == b.to_rows()
    # The hot line must actually have been routed through the split path.
    assert stats["n_giant_lines"] >= 1
    assert stats["n_giant_pairs"] > 0


def test_tiny_input_small_mesh():
    # Regression: cap_giant larger than the whole row buffer must not break the
    # gather slicing (4 triples on 1-/2-device meshes).
    ids, _ = intern_triples(np.asarray(
        [("s1", "p1", "o1"), ("s2", "p1", "o1"), ("s1", "p2", "o2"),
         ("s2", "p2", "o2")], dtype=object))
    want = allatonce.discover(ids, 1).to_rows()
    for d in (1, 2):
        got = sharded.discover_sharded(ids, 1, mesh=make_mesh(d)).to_rows()
        assert got == want


def test_skew_split_device_invariance(mesh8):
    rng = random.Random(12)
    ids, _ = intern_triples(
        np.asarray(skewed_triples(rng, 80, 120), dtype=object))
    want = allatonce.discover(ids, 2).to_rows()
    for d in (1, 4, 8):
        got = sharded.discover_sharded(ids, 2, mesh=make_mesh(d)).to_rows()
        assert got == want, f"mismatch on {d}-device mesh"
