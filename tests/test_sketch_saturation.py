"""The count-min saturation contract (sharded half-approximate 1/1, round 1).

`merge_count_min` (host: int64 sum of partial tables, cap ONCE) is the
reference semantics; `exchange.sketch_allreduce` (device: saturating psum,
cap after EVERY reduction level) is the wire implementation.  The saturation
lemma in ops/sketch.py says they agree bit-for-bit whenever every input is
already <= cap — which `count_min_add`/`count_min_partial` guarantee.  These
tests pin that contract at and past MAX_COUNT_MIN_CAP, the int32 overflow
edge of the chunked accumulation, the partial-build fold, the hierarchical
factorizations (incl. 1xN / Nx1), and the ledger byte model.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from rdfind_tpu.ops import sketch
from rdfind_tpu.parallel import exchange
from rdfind_tpu.parallel.mesh import AXIS, make_mesh, shard_map

D = 8
BITS = 256
K = 2
CAP = sketch.MAX_COUNT_MIN_CAP
FACTORIZATIONS = [(1, 8), (2, 4), (4, 2), (8, 1)]


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest should provide 8 CPU devices"
    return make_mesh(8)


def _partials(seed, n_rows=200, lo=1, hi=50, cap=CAP):
    """D per-device partial tables via the production build entry point."""
    rng = np.random.default_rng(seed)
    parts = []
    for _ in range(D):
        keys = jnp.asarray(rng.integers(0, 40, n_rows), jnp.int32)
        cnts = jnp.asarray(rng.integers(lo, hi, n_rows), jnp.int32)
        valid = jnp.asarray(rng.random(n_rows) < 0.9)
        parts.append(np.asarray(sketch.count_min_partial(
            keys, cnts, valid, bits=BITS, num_hashes=K, cap=cap)))
    return parts


def _device_reduce(mesh, parts, cap, hier):
    def f(t):
        return exchange.sketch_allreduce(t.reshape(-1), AXIS, cap=cap,
                                         hier=hier)
    sm = shard_map(f, mesh=mesh, in_specs=(P(AXIS),), out_specs=P(AXIS),
                   check_vma=False)
    out = np.asarray(jax.jit(sm)(np.stack(parts))).reshape(D, -1)
    # Every device must hold the same reduced table (all-reduce contract).
    for d in range(1, D):
        np.testing.assert_array_equal(out[0], out[d])
    return out[0]


@pytest.mark.parametrize("hier", [None] + FACTORIZATIONS)
def test_device_reduce_matches_host_merge(mesh8, hier):
    """Below saturation: psum-per-level == sum-then-cap, every factorization."""
    parts = _partials(seed=0)
    ref = sketch.merge_count_min(parts, cap=CAP)
    got = _device_reduce(mesh8, parts, CAP, hier)
    np.testing.assert_array_equal(ref, got)


@pytest.mark.parametrize("hier", [None] + FACTORIZATIONS)
def test_agreement_at_and_past_cap(mesh8, hier):
    """Partials hot enough that sums cross MAX_COUNT_MIN_CAP mid-reduction:
    intermediate caps (device) vs one final cap (host) must still agree —
    the saturation lemma's actual content."""
    parts = _partials(seed=1, n_rows=400, lo=CAP // 3, hi=CAP // 2)
    assert max(int(p.max()) for p in parts) == CAP, "fixture must saturate"
    ref = sketch.merge_count_min(parts, cap=CAP)
    assert int(ref.max()) == CAP
    got = _device_reduce(mesh8, parts, CAP, hier)
    np.testing.assert_array_equal(ref, got)


def test_small_cap_agreement(mesh8):
    """A small cap saturates at a different level on different devices; the
    contract is cap-generic, not MAX_COUNT_MIN_CAP-specific."""
    parts = _partials(seed=2, cap=100)
    ref = sketch.merge_count_min(parts, cap=100)
    got = _device_reduce(mesh8, parts, 100, (2, 4))
    np.testing.assert_array_equal(ref, got)


def test_count_min_add_chunk_accumulation_no_wrap():
    """The int-dtype overflow edge: a full 2^14-row scan chunk of rows all
    at the per-row clip bound accumulates 2^14 * (2^16-1) ~ 2^30 in int32
    before the inter-chunk clamp — near, but provably below, wrap.  The
    result must be exactly cap, not a wrapped negative."""
    n = sketch._CM_CHUNK + 7  # spill into a second chunk too
    t = sketch.count_min_add(
        jnp.zeros(n, jnp.int32), jnp.full(n, CAP, jnp.int32),
        jnp.ones(n, bool), bits=32, num_hashes=1, cap=CAP)
    t = np.asarray(t)
    assert (t >= 0).all()
    assert int(t.max()) == CAP


def test_count_min_partial_fold_is_saturating():
    """count_min_partial(table=prev) == min(prev + partial, cap), and folding
    order never matters (associativity under the lemma)."""
    rng = np.random.default_rng(3)
    keys = jnp.asarray(rng.integers(0, 30, 100), jnp.int32)
    cnts = jnp.asarray(rng.integers(1, CAP // 2, 100), jnp.int32)
    valid = jnp.ones(100, bool)
    part = sketch.count_min_partial(keys, cnts, valid, bits=BITS, num_hashes=K)
    prev = jnp.asarray(np.full(BITS, CAP - 10, np.int32))
    folded = np.asarray(sketch.count_min_partial(
        keys, cnts, valid, bits=BITS, num_hashes=K, table=prev))
    ref = np.minimum(np.asarray(prev, np.int64) + np.asarray(part, np.int64),
                     CAP).astype(np.int32)
    np.testing.assert_array_equal(folded, ref)


def test_sketch_allreduce_byte_model():
    """Ledger pin: flat moves d*(d-local) tables across DCN, hierarchical
    d*(hosts-1) — a factor-local reduction (4x at d=8, hosts=2)."""
    b = BITS * 4
    ici_f, dcn_f = exchange.sketch_allreduce_bytes(8, BITS, hosts=2,
                                                   hier=False)
    ici_h, dcn_h = exchange.sketch_allreduce_bytes(8, BITS, hosts=2,
                                                   hier=True)
    assert ici_f == ici_h == 8 * 3 * b
    assert dcn_f == 8 * 4 * b and dcn_h == 8 * 1 * b
    assert dcn_f == 4 * dcn_h
    # Degenerate single-host: no DCN either way.
    assert exchange.sketch_allreduce_bytes(8, BITS, hosts=1, hier=True)[1] == 0
    assert exchange.sketch_allreduce_bytes(8, BITS, hosts=1, hier=False)[1] == 0


def test_log_sketch_allreduce_ledger_entry():
    stats = {}
    part = exchange.log_sketch_allreduce(stats, num_dev=8, bits=BITS,
                                         hosts=2, hier=True)
    e = stats["exchange_sites"][exchange.SKETCH_ALLREDUCE_SITE]
    assert e["calls"] == 1 and e["capacity"] == BITS and e["hier"] == 1
    assert e["ici_bytes"] == part["ici"] and e["dcn_bytes"] == part["dcn"]
    assert e["bytes"] == part["bytes"] == part["ici"] + part["dcn"]
    assert part["reply"] == 0
