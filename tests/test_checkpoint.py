"""Stage-boundary checkpoint/resume through the driver, durability of the
save path (fsync + atomic rename), and the mid-discover progress codec."""

import os
import time

import numpy as np
import pytest

from rdfind_tpu.data import CindTable
from rdfind_tpu.dictionary import Dictionary
from rdfind_tpu.runtime import checkpoint, driver, faults

NT = """\
<http://x/s1> <http://x/p1> "v1" .
<http://x/s2> <http://x/p1> "v1" .
<http://x/s1> <http://x/p2> "v1" .
<http://x/s2> <http://x/p2> "v2" .
<http://x/s3> <http://x/p2> "v2" .
"""


@pytest.fixture
def fixture_nt(tmp_path):
    f = tmp_path / "data.nt"
    f.write_text(NT)
    return str(f)


def make_cfg(fixture_nt, tmp_path, **kw):
    kw = {"min_support": 1, "traversal_strategy": 0, **kw}
    return driver.Config(input_paths=[fixture_nt],
                         checkpoint_dir=str(tmp_path / "ckpt"), **kw)


def test_resume_roundtrip(fixture_nt, tmp_path):
    cfg = make_cfg(fixture_nt, tmp_path)
    first = driver.run(cfg)
    assert "resumed-ingest" not in first.counters
    assert os.path.exists(tmp_path / "ckpt" / "ingest.npz")
    assert os.path.exists(tmp_path / "ckpt" / "discover.npz")

    second = driver.run(cfg)
    assert second.counters["resumed-ingest"] == 1
    assert second.counters["resumed-discover"] == 1
    assert second.table.to_rows() == first.table.to_rows()
    assert list(second.dictionary.values) == list(first.dictionary.values)
    np.testing.assert_array_equal(second.triples, first.triples)


def test_flag_change_invalidates_discover_not_ingest(fixture_nt, tmp_path):
    driver.run(make_cfg(fixture_nt, tmp_path))
    res = driver.run(make_cfg(fixture_nt, tmp_path, min_support=2))
    assert res.counters["resumed-ingest"] == 1
    assert "resumed-discover" not in res.counters
    # And the new discover result is checkpointed under its own fingerprint.
    res2 = driver.run(make_cfg(fixture_nt, tmp_path, min_support=2))
    assert res2.counters["resumed-discover"] == 1
    assert res2.table.to_rows() == res.table.to_rows()


def test_input_change_invalidates_everything(fixture_nt, tmp_path):
    driver.run(make_cfg(fixture_nt, tmp_path))
    time.sleep(0.01)
    with open(fixture_nt, "a") as f:
        f.write('<http://x/s4> <http://x/p1> "v9" .\n')
    res = driver.run(make_cfg(fixture_nt, tmp_path))
    assert "resumed-ingest" not in res.counters
    assert "resumed-discover" not in res.counters
    assert res.counters["input-triples"] == 6


def test_corrupt_checkpoint_is_a_miss(fixture_nt, tmp_path):
    cfg = make_cfg(fixture_nt, tmp_path)
    first = driver.run(cfg)
    with open(tmp_path / "ckpt" / "discover.npz", "wb") as f:
        f.write(b"not an npz")
    res = driver.run(cfg)
    assert "resumed-discover" not in res.counters
    assert res.table.to_rows() == first.table.to_rows()


def test_ingest_codec_roundtrip():
    ids = np.arange(12, dtype=np.int32).reshape(4, 3)
    values = np.asarray(["", "a", "héllo", "züüü"], object)
    out_ids, d = checkpoint.decode_ingest(
        checkpoint.encode_ingest(ids, Dictionary(values)))
    np.testing.assert_array_equal(out_ids, ids)
    assert list(d.values) == list(values)


def test_cind_codec_roundtrip():
    t = CindTable(*(np.arange(i, i + 3, dtype=np.int64) for i in range(7)))
    out = checkpoint.decode_cinds(checkpoint.encode_cinds(t))
    assert out.to_rows() == t.to_rows()


def test_stats_survive_resume(fixture_nt, tmp_path):
    """stat-* counters come back identical on a resumed discover stage."""
    cfg = make_cfg(fixture_nt, tmp_path)
    first = driver.run(cfg)
    first_stats = {k: v for k, v in first.counters.items()
                   if k.startswith("stat-") and isinstance(v, (int, float, str))}
    assert first_stats, "expected the pipeline to record scalar stats"
    second = driver.run(cfg)
    assert second.counters["resumed-discover"] == 1
    for k, v in first_stats.items():
        assert second.counters.get(k) == v, k


def test_truncated_checkpoint_is_clean_miss(tmp_path):
    """A zero-length or torn .npz (host crash mid-write before the fsync
    hardening, partial copy, disk-full) must read as a miss, never crash."""
    store = checkpoint.CheckpointStore(str(tmp_path))
    fp = checkpoint.fingerprint({"x": 1})
    store.save("stage", fp, {"a": np.arange(1000)})
    assert store.load("stage", fp) is not None

    path = tmp_path / "stage.npz"
    raw = path.read_bytes()
    path.write_bytes(b"")  # zero-length file
    assert store.load("stage", fp) is None
    path.write_bytes(raw[: len(raw) // 2])  # torn tail
    assert store.load("stage", fp) is None
    path.write_bytes(raw)  # intact bytes still load
    assert store.load("stage", fp) is not None


def test_save_leaves_no_tmp_file(tmp_path):
    store = checkpoint.CheckpointStore(str(tmp_path))
    store.save("stage", "fp", {"a": np.arange(4)})
    assert sorted(os.listdir(tmp_path)) == ["stage.npz"]
    store.discard("stage")
    assert os.listdir(tmp_path) == []
    store.discard("stage")  # idempotent


def test_input_signature_missing_file_is_diagnosed(tmp_path, capsys):
    f = tmp_path / "gone.nt"
    f.write_text("x")
    sig_present = checkpoint.input_signature([str(f)])
    f.unlink()
    sig_missing = checkpoint.input_signature([str(f)])  # must not raise
    assert sig_missing[0][1:] == [-1, -1]
    assert sig_present != sig_missing  # dependent checkpoints go stale
    assert "not statable" in capsys.readouterr().err


def test_progress_codec_roundtrip():
    parts = {
        0: ([np.arange(3, dtype=np.int64), np.ones(2, np.int32)], (1, 2, 3)),
        2: ([np.zeros(0, np.int64), np.arange(4, dtype=np.int32)], (4, 5, 6)),
    }
    snap = checkpoint.decode_progress(
        checkpoint.encode_progress(parts, num_dev=8, n_pass=3))
    assert snap.num_dev == 8 and snap.n_pass == 3
    out = snap.parts
    assert sorted(out) == [0, 2]
    for p in parts:
        got_blocks, got_tele = out[p]
        want_blocks, want_tele = parts[p]
        assert got_tele == want_tele
        assert len(got_blocks) == len(want_blocks)
        for g, w in zip(got_blocks, want_blocks):
            np.testing.assert_array_equal(g, w)


def test_progress_store_roundtrip_and_cleanup(tmp_path):
    store = checkpoint.ProgressStore(
        checkpoint.CheckpointStore(str(tmp_path)), "base")
    stage, fp = store.phase_fp("cind", 0)
    parts = {0: ([np.arange(5)], (7, 8, 9))}
    store.submit(stage, fp, parts, num_dev=8, n_pass=3)
    store.flush()
    snap = store.load(stage, fp)
    assert snap is not None
    assert snap.num_dev == 8 and snap.n_pass == 3
    # The fingerprint is mesh-portable: neither num_dev nor n_pass feeds it
    # (they ride the snapshot as metadata and are resolved at resume time),
    # but the phase extras still do.
    stage2, fp2 = store.phase_fp("cind", 0)
    assert stage2 == stage and fp2 == fp
    _, fp3 = store.phase_fp("cind", 0, extra={"what": "other"})
    assert fp3 != fp
    store.cleanup()
    assert store.load(stage, fp) is None


def test_checkpoint_write_failure_degrades(fixture_nt, tmp_path, monkeypatch):
    """An injected checkpoint-write fault must not fail the run — it only
    costs the NEXT run its resume (counted in checkpoint-errors)."""
    cfg = make_cfg(fixture_nt, tmp_path)
    monkeypatch.setenv("RDFIND_FAULTS", "checkpoint_write:times=-1")
    faults.reset()
    try:
        res = driver.run(cfg)
    finally:
        monkeypatch.delenv("RDFIND_FAULTS")
        faults.reset()
    assert res.counters["checkpoint-errors"] >= 1
    assert len(res.table) > 0
    # Nothing durable was written, so the next (fault-free) run re-ingests.
    res2 = driver.run(cfg)
    assert "resumed-ingest" not in res2.counters
    assert res2.table.to_rows() == res.table.to_rows()


def test_format_version_in_fingerprint(monkeypatch):
    fp1 = checkpoint.fingerprint({"a": 1})
    monkeypatch.setattr(checkpoint, "CHECKPOINT_FORMAT",
                        checkpoint.CHECKPOINT_FORMAT + 1)
    assert checkpoint.fingerprint({"a": 1}) != fp1


def test_stats_codec_keeps_scalars_only():
    stats = {"n": 3, "f": 1.5, "s": "x", "b": True,
             "arr": np.arange(3), "tup": (1, 2)}
    out = checkpoint.decode_stats(checkpoint.encode_stats(stats))
    assert out == {"n": 3, "f": 1.5, "s": "x", "b": True}
